"""Runtime telemetry subsystem: metrics registry, run-event log,
device/collective accounting, live introspection.

Analog of the reference's operational instrumentation
(``Common::Timer``/``FunctionTimer``, common.h:973,1037, plus the
per-iteration logger stream) rebuilt for long-running TPU training:

- :mod:`~lightgbm_tpu.telemetry.core` — Counter/Gauge/RingHistogram +
  labelled families and the Prometheus text render, shared with
  serving (whose metrics module these primitives came from);
- :mod:`~lightgbm_tpu.telemetry.events` — append-only JSONL run-event
  log with typed records, written only at existing sync points;
- :mod:`~lightgbm_tpu.telemetry.device` — HBM watermarks, compile
  counters, static-×-count collective-traffic gauges (no readbacks);
- :mod:`~lightgbm_tpu.telemetry.exporter` — the opt-in
  ``telemetry_port`` HTTP server (/metrics /events /healthz /trace)
  and the SIGUSR1 dump handler;
- :mod:`~lightgbm_tpu.telemetry.monitor` — ``python -m lightgbm_tpu
  monitor <run_dir>``: render an event log into a report, or
  ``--check`` its schema.

:class:`TelemetrySession` composes these for ``engine.train``: the
engine calls the ``on_*`` hooks exclusively from host code that has
already synced (the eval-cadence sync block, checkpoint writes, fault
handlers), so a telemetry-enabled run issues exactly the same device
syncs as a bare one.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import log, profiler
from . import events as _events
from .core import Counter, Gauge, MetricsRegistry, RingHistogram
from .device import CollectiveWatch, DeviceWatch
from .events import EventLog
from .exporter import IntrospectionServer, install_sigusr1

__all__ = ["Counter", "Gauge", "RingHistogram", "MetricsRegistry",
           "EventLog", "IntrospectionServer", "TelemetrySession",
           "active_session"]

_SESSION: Optional["TelemetrySession"] = None


def active_session() -> Optional["TelemetrySession"]:
    """The TelemetrySession of the currently-running train(), if any
    (how a test or sidecar discovers the bound port)."""
    return _SESSION


class TelemetrySession:
    """One training run's telemetry: registry + event log + device
    watches + optional HTTP exporter, created by ``engine.train`` when
    ``telemetry_port``/``event_log`` ask for it."""

    def __init__(self, event_log_path: Optional[str] = None,
                 port: Optional[int] = None):
        self.registry = MetricsRegistry()
        self.events: Optional[EventLog] = (
            EventLog(event_log_path) if event_log_path else None)
        self.port: Optional[int] = None
        self._want_port = port
        self.server: Optional[IntrospectionServer] = None
        self.device = DeviceWatch(self.registry)
        self.collectives = CollectiveWatch(self.registry,
                                           self._trees_built)
        self.phase_totals = profiler.PhaseTotals()
        self._booster = None
        self._restore_sig = lambda: None
        self._started = False
        # progress state, all host-side
        self._iter = 0
        self._t0 = time.monotonic()
        self._last_sync_t = self._t0
        self._last_sync_iter = 0
        self._last_phase: Dict[str, Tuple[float, int]] = {}
        self._c_iters = self.registry.counter(
            "train_iterations_total", "Boosting iterations completed")
        self._c_trees = self.registry.counter(
            "train_trees_total", "Trees materialized or pending")
        self._g_ms_tree = self.registry.gauge(
            "train_ms_per_tree",
            "Wall ms per tree over the last sync window")
        self._g_iter = self.registry.gauge(
            "train_iteration", "Current iteration (1-based, completed)")
        self._g_metric = self.registry.gauge(
            "train_eval_metric", "Last evaluated metric values",
            labels=("data", "metric"))
        self._g_phase = self.registry.gauge(
            "train_phase_seconds_total",
            "Host wall seconds per training phase (phases.py names)",
            labels=("phase",))
        self._c_syncs = self.registry.gauge(
            "train_host_syncs_total",
            "Booster host syncs (device ring drains)",
            fn=self._host_syncs)
        self._c_nan = self.registry.counter(
            "train_nan_guard_total", "Nan-guard incidents")
        self._c_ckpt = self.registry.counter(
            "train_checkpoints_total", "Checkpoint writes/restores",
            labels=("action",))
        self.registry.gauge("train_uptime_seconds",
                            "Seconds since telemetry start",
                            fn=lambda: time.monotonic() - self._t0)
        # roofline gauges (ISSUE 11): XLA's cost_analysis price of the
        # compiled fused step × the measured iteration rate. The cost
        # report and instruction→phase maps are built ON THE TRAINING
        # THREAD at the first sync after a scrape asks for them
        # (lower()-ing the fused jit from the HTTP thread would race a
        # concurrent dispatch's trace-time attribute rebinding), so the
        # first scrape reads 0 and arms the want-flag.
        self._perf_want = False
        self._cost_cache: Any = None      # None | False | CostReport
        self._phase_maps: Dict[str, Dict[str, str]] = {}
        self.registry.gauge(
            "train_fused_flops_per_iter",
            "XLA cost_analysis flops of one compiled fused step",
            fn=lambda: self._cost_field("flops"))
        self.registry.gauge(
            "train_fused_bytes_per_iter",
            "XLA cost_analysis bytes accessed of one fused step",
            fn=lambda: self._cost_field("bytes_accessed"))
        self._g_tflops = self.registry.gauge(
            "train_achieved_tflops",
            "Achieved TFLOP/s: fused-step flops x iteration rate")
        self._g_mfu = self.registry.gauge(
            "train_mfu",
            "Achieved TFLOP/s vs chip peak (known TPU chips only)")

    @classmethod
    def from_config(cls, cfg, params: Dict[str, Any]
                    ) -> Optional["TelemetrySession"]:
        """None unless telemetry_port or event_log enables the
        subsystem (param first; the env var covers unmodified
        entry points)."""
        port = int(cfg.telemetry_port)
        if port < 0:
            env = os.environ.get("LIGHTGBM_TPU_TELEMETRY_PORT")
            if env is not None and env.strip() != "":
                try:
                    port = int(env)
                except ValueError:
                    log.warning("ignoring non-integer "
                                f"LIGHTGBM_TPU_TELEMETRY_PORT={env!r}")
        path = str(cfg.event_log).strip()
        if path == "auto":
            path = str(cfg.output_model) + ".events.jsonl"
        if port < 0 and not path:
            return None
        return cls(event_log_path=path or None,
                   port=port if port >= 0 else None)

    # -- helpers -------------------------------------------------------
    def _gb(self):
        b = self._booster
        return getattr(b, "_gbdt", None) if b is not None else None

    def _trees_built(self) -> int:
        gb = self._gb()
        return int(gb.num_trees()) if gb is not None else 0

    def _host_syncs(self) -> int:
        gb = self._gb()
        return int(getattr(gb, "host_sync_count", 0)) if gb else 0

    # -- cost model / phase maps (built at sync points only) ----------
    def _cost_field(self, attr: str) -> float:
        """Gauge fn: read the cached fused-step CostReport, arming the
        want-flag on a miss (next on_sync builds; scrapes never
        compile)."""
        rep = self._cost_cache
        if rep is None:
            self._perf_want = True
        return float(getattr(rep, attr, 0.0) or 0.0) if rep else 0.0

    def phase_maps(self) -> Dict[str, Dict[str, str]]:
        """Instruction→phase maps for trace captures. Same contract as
        the gauges: cached-or-arm, never build off the training
        thread."""
        if not self._phase_maps:
            self._perf_want = True
        return dict(self._phase_maps)

    def _build_perf(self) -> None:
        """Build the fused-step CostReport + phase maps (training
        thread, at a sync point). force=False: uses the driver's
        already-traced jit, refuses to trigger a fresh trace."""
        from . import costmodel
        try:
            compiled = costmodel.fused_compiled(self._booster,
                                                force=False)
        except Exception:  # noqa: BLE001 — perf extras never fault a run
            compiled = None
        if compiled is None:
            self._cost_cache = False
            return
        try:
            text = compiled.as_text()
            self._cost_cache = costmodel.cost_report(
                compiled, "fused_step", hlo_text=text)
            mod, table = costmodel.instruction_phase_map(text)
            if table:
                self._phase_maps = {mod: table}
            if self.events is not None:
                rep = self._cost_cache
                self.events.append(
                    "cost_model", label="fused_step",
                    flops=rep.flops, bytes_accessed=rep.bytes_accessed,
                    peak_bytes=rep.peak_bytes, n_ops=rep.n_ops)
        except Exception:  # noqa: BLE001
            self._cost_cache = False

    # -- lifecycle (engine.train) --------------------------------------
    def begin_run(self, booster, cfg, params: Dict[str, Any],
                  fingerprint: Optional[str],
                  resumed_from: Optional[Tuple[str, int]] = None) -> None:
        """Start watches/exporter and write the run header. On resume,
        splice the existing log to the restored iteration first so the
        re-emitted records chain without duplicates."""
        global _SESSION
        self._booster = booster
        booster._ensure_gbdt()
        gb = self._gb()
        self.collectives.attach(gb)
        self._iter = self._last_sync_iter = booster.current_iteration()
        self._last_sync_t = time.monotonic()
        if self.events is not None:
            if resumed_from is not None:
                self.events.splice_to_iteration(resumed_from[1])
            self.events.append("run_header", **self._header(
                gb, cfg, params, fingerprint))
            if resumed_from is not None:
                self.events.append("resume", iter=resumed_from[1],
                                   path=resumed_from[0])
            _events.set_active(self.events)
        profiler.add_phase_collector(self.phase_totals)
        self.device.start()
        self.device.sample()
        if self._want_port is not None:
            capture_root = None
            if self.events is not None and self.events.path:
                capture_root = os.path.join(
                    os.path.dirname(os.path.abspath(self.events.path))
                    or ".", "traces")
            self.server = IntrospectionServer(
                self.registry, event_log=self.events,
                health_fn=self._health,
                port=int(self._want_port),
                capture_root=capture_root,
                phase_map_fn=self.phase_maps)
            try:
                self.port = self.server.start()
            except OSError as e:
                # fail open: a taken port (another run, a stale
                # sidecar) must not kill a healthy training job — the
                # exporter is observability, not a dependency
                log.warning(
                    f"telemetry: cannot bind exporter port "
                    f"{self._want_port} ({e}); continuing without "
                    "live introspection")
                self.server = None
                self.port = None
            else:
                log.info("telemetry: serving "
                         f"http://127.0.0.1:{self.port} "
                         "(/metrics /events /healthz /trace)")
        self._restore_sig = install_sigusr1(self.dump_to_log)
        self._started = True
        _SESSION = self

    def _header(self, gb, cfg, params, fingerprint) -> Dict[str, Any]:
        import jax
        import numpy as np

        from .. import __version__ as _ver
        plan = getattr(gb, "plan", None)
        return {
            "fingerprint": fingerprint,
            "driver": "fused" if getattr(gb, "fused_ok", False)
                      else "legacy",
            "versions": {"lightgbm_tpu": _ver, "jax": jax.__version__,
                         "numpy": np.__version__},
            "tree_learner": str(cfg.tree_learner),
            "parallel_mode": (getattr(plan, "parallel_mode", "serial")
                              if plan is not None else "serial"),
            "num_shards": (int(getattr(plan, "num_shards", 1))
                           if plan is not None else 1),
            "dp_hist_merge": (str(getattr(plan, "hist_merge", ""))
                              if plan is not None else ""),
            "class_batch": bool(getattr(gb, "class_batch_ok", False)),
            "num_class": int(getattr(gb, "K", 1)),
            "objective": str(cfg.objective),
            "num_leaves": int(cfg.num_leaves),
            "eval_period": int(cfg.eval_period),
            "devices": [f"{d.platform}:{d.id}" for d in jax.devices()],
        }

    def _health(self) -> Dict[str, Any]:
        return {"iteration": self._iter, "trees": self._trees_built(),
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "host_syncs": self._host_syncs()}

    # -- engine hooks (sync points only) -------------------------------
    def on_sync(self, iteration: int,
                evals: Optional[List[tuple]] = None) -> None:
        """Eval-cadence sync point: everything recorded here is already
        on the host (the booster just drained its ring)."""
        now = time.monotonic()
        gb = self._gb()
        k = int(getattr(gb, "K", 1)) if gb is not None else 1
        d_iter = max(iteration - self._last_sync_iter, 0)
        ms_tree = ((now - self._last_sync_t) * 1e3 / (d_iter * k)
                   if d_iter > 0 else 0.0)
        metrics = {f"{name}:{metric}": float(value)
                   for name, metric, value, _ in (evals or [])}
        phase_s: Dict[str, Dict[str, float]] = {}
        for name, tot, cnt in self.phase_totals.items():
            p_tot, p_cnt = self._last_phase.get(name, (0.0, 0))
            if d_iter > 0:
                phase_s[name] = {
                    "s_per_iter": (tot - p_tot) / d_iter,
                    "spans_per_iter": (cnt - p_cnt) / d_iter}
            self._last_phase[name] = (tot, cnt)
            self._g_phase.labels(name).set(tot)
        self._c_iters.inc(d_iter)
        self._c_trees.inc(d_iter * k)
        self._g_iter.set(iteration)
        if d_iter > 0:
            self._g_ms_tree.set(ms_tree)
        if self._perf_want and self._cost_cache is None:
            self._build_perf()
        rep = self._cost_cache
        if rep and d_iter > 0 and ms_tree > 0:
            achieved = rep.flops / (ms_tree / 1e3) / 1e12
            self._g_tflops.set(achieved)
            from .costmodel import chip_peaks
            peaks = chip_peaks()
            if peaks is not None:
                self._g_mfu.set(achieved / peaks[1])
        for (name, metric), value in [((n, m), v) for n, m, v, _ in
                                      (evals or [])]:
            self._g_metric.labels(name, metric).set(value)
        self.device.sample()
        self._iter = iteration
        self._last_sync_iter = iteration
        self._last_sync_t = now
        if self.events is not None and d_iter > 0:
            self.events.append("iteration", iter=iteration,
                               ms_per_tree=round(ms_tree, 3),
                               metrics=metrics, phase_s=phase_s,
                               host_syncs=self._host_syncs())

    def on_checkpoint(self, action: str, iteration: int,
                      path: str, ok: bool = True) -> None:
        self._c_ckpt.labels(action if ok else f"{action}_failed").inc()
        if self.events is not None:
            rec = {"action": action, "iter": iteration, "path": path}
            if not ok:
                rec["ok"] = False
            self.events.append("checkpoint", **rec)

    def on_reshard(self, iteration: int, from_topo: Dict[str, Any],
                   to_topo: Dict[str, Any]) -> None:
        """Elastic resume re-sharded checkpoint state onto a different
        topology (called right after begin_run, already synced)."""
        if self.events is not None:
            self.events.append("reshard", iter=iteration,
                               **{"from": from_topo, "to": to_topo})

    def on_preemption(self, signum: int, iteration: int) -> None:
        if self.events is not None:
            self.events.append("preemption", signum=int(signum),
                               iter=iteration)

    def on_nan_guard(self, iteration: int, policy: str,
                     action: str) -> None:
        self._c_nan.inc()
        if self.events is not None:
            self.events.append("nan_guard", iter=iteration,
                               policy=policy, action=action)

    def on_early_stop(self, iteration: int, best_iter: int) -> None:
        if self.events is not None:
            self.events.append("early_stop", iter=iteration,
                               best_iter=best_iter)

    def dump_to_log(self) -> None:
        """SIGUSR1: one human-readable state dump through log.info."""
        snap = self._health()
        log.info(f"telemetry dump: iteration={snap['iteration']} "
                 f"trees={snap['trees']} uptime={snap['uptime_s']}s "
                 f"host_syncs={snap['host_syncs']}")
        log.info("telemetry phase totals:\n"
                 + self.phase_totals.render(self._iter or None))

    def close(self, ended: bool) -> None:
        """Tear down in reverse order. ``ended`` False (an exception is
        unwinding) suppresses train_end so the fault record written by
        the handler stays the log's last word."""
        global _SESSION
        if _SESSION is self:
            _SESSION = None
        _events.set_active(None)
        self._restore_sig()
        self._restore_sig = lambda: None
        if self.server is not None:
            self.server.stop()
            self.server = None
        if self._started:
            profiler.remove_phase_collector(self.phase_totals)
            self.device.stop()
            self._started = False
        if self.events is not None:
            if ended:
                self.events.append(
                    "train_end", iter=self._iter,
                    trees=self._trees_built(),
                    wall_s=round(time.monotonic() - self._t0, 3))
            self.events.close()
