"""Generic metric primitives + registry, shared by training and serving.

No reference analog — LightGBM's operational visibility stops at the
logger and the TIMETAG timers (common.h:973,1037); a TPU training run
needs live counters the way the serving layer already had them. This
module generalizes the primitives that were private to
``serving/metrics.py`` (Counter, RingHistogram, the Prometheus text
renderer) into a registry both subsystems mount:

- :class:`Counter` — monotonic, one uncontended ``threading.Lock`` per
  increment (~100 ns): CPython attribute ``+=`` is NOT atomic
  (LOAD/ADD/STORE can interleave at the bytecode boundary), so the lock
  is the cheapest *correct* primitive; reads are single attribute loads
  and need none.
- :class:`Gauge` — last-write-wins value, or a zero-storage callback
  gauge (``Gauge(fn=...)``) evaluated only at scrape time, which is how
  the device-accounting gauges (telemetry/device.py) avoid doing any
  work on the training path.
- :class:`RingHistogram` — fixed-size ring of observations; percentiles
  are computed only at scrape time over the last ``size`` observations,
  so the hot path never sorts and memory never grows with traffic.
- :class:`MetricsRegistry` — named families (optionally labelled),
  rendered in the Prometheus text exposition format
  (text/plain; version=0.0.4). External metric sets that keep their own
  storage (ServingMetrics) mount via :meth:`~MetricsRegistry.
  register_collector`, which appends their rendered text verbatim — the
  serving families' bytes are pinned by tests and must not be
  re-rendered through a second formatter.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

__all__ = ["Counter", "Gauge", "RingHistogram", "MetricsRegistry",
           "render_counter", "render_summary"]


class Counter:
    """Monotonic counter with optional labelled children."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value  # single attribute load: atomic under the GIL


class Gauge:
    """Last-write-wins value, or a callback evaluated at scrape time.

    Callback gauges (``Gauge(fn=...)``) store nothing and cost nothing
    until a scrape asks; a callback that raises reads as 0.0 rather
    than failing the whole ``/metrics`` render mid-run.
    """

    __slots__ = ("_value", "_fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._value = 0.0
        self._fn = fn

    def set(self, value: float):
        self._value = float(value)  # single store: atomic under the GIL

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return 0.0
        return self._value


class RingHistogram:
    """Fixed-size ring of float observations (latencies, batch sizes).

    ``observe`` is O(1); quantiles/mean are computed at scrape time over
    the retained window (the last ``size`` observations), which is the
    operationally useful view — a dashboard wants *recent* p99, not the
    all-time one that a cumulative histogram would smear.
    """

    __slots__ = ("_lock", "_buf", "_n")

    def __init__(self, size: int = 4096):
        self._lock = threading.Lock()
        self._buf = np.zeros(int(size), np.float64)
        self._n = 0

    def observe(self, value: float):
        with self._lock:
            self._buf[self._n % len(self._buf)] = value
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    def window(self) -> np.ndarray:
        """Copy of the retained observations (unordered)."""
        with self._lock:
            return self._buf[: min(self._n, len(self._buf))].copy()

    def summary(self, qs: Iterable[float] = (0.5, 0.95, 0.99)
                ) -> Tuple[Dict[float, float], int, float]:
        """({quantile: value}, total_count, window_mean)."""
        w = self.window()
        if w.size == 0:
            return {q: 0.0 for q in qs}, self._n, 0.0
        return ({q: float(np.percentile(w, 100.0 * q)) for q in qs},
                self._n, float(w.mean()))


# ----------------------------------------------------------------------
# Prometheus text rendering — the exact byte format the serving layer
# has always emitted (tests pin it); both render paths share these.

def render_counter(out: List[str], name: str, help_: str,
                   pairs: Iterable[Tuple[str, int]]) -> None:
    out.append(f"# HELP {name} {help_}")
    out.append(f"# TYPE {name} counter")
    for labels, v in pairs:
        out.append(f"{name}{labels} {v}")


def render_summary(out: List[str], name: str, help_: str,
                   hist: RingHistogram, scale: float = 1.0) -> None:
    qs, cnt, mean = hist.summary()
    out.append(f"# HELP {name} {help_}")
    out.append(f"# TYPE {name} summary")
    for q, v in qs.items():
        out.append(f'{name}{{quantile="{q:g}"}} {v * scale:.9g}')
    out.append(f"{name}_count {cnt}")
    out.append(f"{name}_mean {mean * scale:.9g}")


def _label_str(names: Tuple[str, ...], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in zip(names, values))
    return "{" + inner + "}"


class _Family:
    """One named metric family: unlabelled (a single child under the
    empty label set) or labelled (children created on first use, like
    ServingMetrics' per-model counter maps)."""

    __slots__ = ("kind", "name", "help", "label_names", "_children",
                 "_lock", "_make", "_scale")

    def __init__(self, kind: str, name: str, help_: str,
                 label_names: Tuple[str, ...], make):
        self.kind = kind
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()
        self._make = make
        self._scale = 1.0

    def labels(self, *values: str):
        values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._make())
        return child

    def child_items(self) -> List[Tuple[str, object]]:
        with self._lock:
            items = sorted(self._children.items())
        return [(_label_str(self.label_names, k), c) for k, c in items]


class MetricsRegistry:
    """Named metric families + external collectors, one Prometheus
    render. Training creates one per run (telemetry session); serving
    creates one per server and mounts its ServingMetrics as a
    collector, so ``/metrics`` on either side is a single
    ``registry.render()``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: List[_Family] = []
        self._by_name: Dict[str, _Family] = {}
        self._collectors: List[Tuple[str, Callable[[], str]]] = []

    # -- family constructors (idempotent by name) ----------------------
    def _family(self, kind: str, name: str, help_: str,
                labels: Tuple[str, ...], make) -> _Family:
        with self._lock:
            fam = self._by_name.get(name)
            if fam is None:
                fam = _Family(kind, name, help_, labels, make)
                self._families.append(fam)
                self._by_name[name] = fam
            elif fam.kind != kind or fam.label_names != labels:
                raise ValueError(f"metric {name!r} re-registered with a "
                                 f"different kind or label set")
        return fam

    def counter(self, name: str, help_: str,
                labels: Tuple[str, ...] = ()) -> object:
        fam = self._family("counter", name, help_, tuple(labels), Counter)
        return fam if labels else fam.labels()

    def gauge(self, name: str, help_: str, labels: Tuple[str, ...] = (),
              fn: Optional[Callable[[], float]] = None) -> object:
        make = (lambda: Gauge(fn)) if fn is not None else Gauge
        fam = self._family("gauge", name, help_, tuple(labels), make)
        return fam if labels else fam.labels()

    def summary(self, name: str, help_: str, size: int = 4096,
                scale: float = 1.0) -> RingHistogram:
        make = lambda: RingHistogram(size)  # noqa: E731
        fam = self._family("summary", name, help_, (), make)
        fam._scale = scale  # type: ignore[attr-defined]
        return fam.labels()

    # -- external metric sets (serving) --------------------------------
    def register_collector(self, name: str,
                           fn: Callable[[], str]) -> None:
        """Mount an external render (replaces an existing collector of
        the same name — server restarts re-register, never stack)."""
        with self._lock:
            self._collectors = [(n, f) for n, f in self._collectors
                                if n != name]
            self._collectors.append((name, fn))

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors = [(n, f) for n, f in self._collectors
                                if n != name]

    # -- export --------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition (text/plain; version=0.0.4)."""
        out: List[str] = []
        with self._lock:
            families = list(self._families)
            collectors = list(self._collectors)
        for fam in families:
            children = fam.child_items()
            if fam.kind == "counter":
                render_counter(out, fam.name, fam.help,
                               [(ls, c.value) for ls, c in children]
                               or [("", 0)])
            elif fam.kind == "gauge":
                out.append(f"# HELP {fam.name} {fam.help}")
                out.append(f"# TYPE {fam.name} gauge")
                for ls, c in (children or [("", Gauge())]):
                    out.append(f"{fam.name}{ls} {c.value:.9g}")
            else:  # summary
                scale = getattr(fam, "_scale", 1.0)
                for ls, hist in children:
                    render_summary(out, fam.name, fam.help, hist, scale)
        text = "\n".join(out) + "\n" if out else ""
        for _, fn in collectors:
            try:
                text += fn()
            except Exception:
                pass  # a dead collector must not fail the scrape
        return text

    def snapshot(self) -> Dict[str, object]:
        """Plain-dict view of every family (SIGUSR1 dump, /healthz)."""
        snap: Dict[str, object] = {}
        with self._lock:
            families = list(self._families)
        for fam in families:
            if fam.kind == "summary":
                for _, hist in fam.child_items():
                    qs, cnt, mean = hist.summary()
                    snap[fam.name] = {"count": cnt, "mean": mean,
                                      "quantiles": {f"{q:g}": v
                                                    for q, v in qs.items()}}
            else:
                vals = {ls or "": c.value for ls, c in fam.child_items()}
                snap[fam.name] = (vals.get("", 0) if list(vals) == [""]
                                  else vals)
        return snap


# Re-exported for API symmetry with time-based modules; keeps callers
# from importing time directly just to timestamp a gauge.
monotonic = time.monotonic
